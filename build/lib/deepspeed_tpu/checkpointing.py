"""Activation checkpointing API surface.

Analog of ``deepspeed.checkpointing`` (runtime/activation_checkpointing/
checkpointing.py: ``checkpoint`` :948, ``configure`` , partitioned/CPU
variants :377/:474).  On TPU the machinery is ``jax.checkpoint``; this
module keeps the reference's call signatures so ported Megatron-style code
runs unchanged, mapping its knobs onto remat policies:

* ``partition_activations`` → handled by GSPMD sharding (activations are
  already sharded over the mesh; nothing to split by hand)
* ``cpu_checkpointing`` → ``offload_dots`` policy (save matmul outputs to
  pinned host memory)
* ``contiguous_memory_optimization``/``synchronize`` → no-ops (XLA owns
  layout and scheduling)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax

_CONFIG: Dict[str, Any] = {
    "partition_activations": False,
    "cpu_checkpointing": False,
    "contiguous_memory_optimization": False,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "policy": "nothing_saveable",
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None) -> None:
    """Ref checkpointing.configure — records knobs; ``checkpoint_in_cpu``
    selects the host-offload remat policy."""
    if partition_activations is not None:
        _CONFIG["partition_activations"] = bool(partition_activations)
    if checkpoint_in_cpu is not None:
        _CONFIG["cpu_checkpointing"] = bool(checkpoint_in_cpu)
        _CONFIG["policy"] = "offload_dots" if checkpoint_in_cpu \
            else "nothing_saveable"
    if contiguous_checkpointing is not None:
        _CONFIG["contiguous_memory_optimization"] = bool(contiguous_checkpointing)
    if synchronize is not None:
        _CONFIG["synchronize_checkpoint_boundary"] = bool(synchronize)
    if profile is not None:
        _CONFIG["profile"] = bool(profile)


def _policy():
    name = _CONFIG["policy"]
    if name == "offload_dots":
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    if name and name != "nothing_saveable":
        return getattr(jax.checkpoint_policies, name, None)
    return None


def checkpoint(function: Callable, *args):
    """Ref checkpointing.checkpoint(function, *args): run ``function`` under
    rematerialisation and return its output."""
    return jax.checkpoint(function, policy=_policy(), prevent_cse=False)(*args)


def checkpoint_wrapper(function: Callable) -> Callable:
    """Decorator form."""
    return jax.checkpoint(function, policy=_policy(), prevent_cse=False)


def is_configured() -> bool:
    return True


def get_config() -> Dict[str, Any]:
    return dict(_CONFIG)


def reset() -> None:
    """Ref checkpointing.reset — clears buffers; here: restore defaults."""
    _CONFIG.update(partition_activations=False, cpu_checkpointing=False,
                   contiguous_memory_optimization=False,
                   synchronize_checkpoint_boundary=False, profile=False,
                   policy="nothing_saveable")


class CheckpointFunction:
    """Name-parity shim (ref CheckpointFunction autograd.Function): calling
    applies :func:`checkpoint`."""

    @staticmethod
    def apply(function, *args):
        return checkpoint(function, *args)
