"""QuantizedParameter — int-quantized storage with on-the-fly dequant.

Analog of ``deepspeed/linear/quantization.py`` (``QuantizedParameter``
:18): a frozen weight stored as int8 (or packed int4) + per-group scales,
dequantized inside the jitted forward so the matmul reads bf16 while HBM
holds the compressed bytes.  Built on the blockwise quantizer kernels in
``deepspeed_tpu.ops.quantizer`` (the TPU analog of csrc/quantization).
"""

from __future__ import annotations

import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer import (dequantize_blockwise, pack_int4,
                                         quantize_blockwise, unpack_int4)


class QuantizedParameter:
    """Quantize once at construction; ``dequantized()`` inside jit.

    q_bits 8 → int8 storage; 4 → two nibbles per byte. Grouping is along
    the last dim (``group_size`` clipped to it).
    """

    def __init__(self, weight, q_bits: int = 8, group_size: int = 512):
        if q_bits not in (4, 8):
            raise ValueError(f"q_bits must be 4 or 8, got {q_bits}")
        self.shape = tuple(weight.shape)
        self.dtype = weight.dtype
        self.q_bits = q_bits
        n = self.shape[-1]
        group_size = min(group_size, n)
        while n % group_size != 0:  # shrink to a divisor of the last dim
            group_size -= 1
        self.group_size = group_size
        q, scale, zero = quantize_blockwise(weight, num_bits=q_bits,
                                            group_size=group_size)
        self.scale = scale
        self.zero = zero
        self.data = pack_int4(q) if q_bits == 4 else q

    def dequantized(self) -> jnp.ndarray:
        q = unpack_int4(self.data) if self.q_bits == 4 else self.data
        w = dequantize_blockwise(q, self.scale, self.zero,
                                 num_bits=self.q_bits)
        return w.astype(self.dtype)

    @property
    def nbytes(self) -> int:
        return int(self.data.size * self.data.dtype.itemsize
                   + self.scale.size * self.scale.dtype.itemsize)
