"""Optimized linear: quantized base weights + LoRA adapters.

Analog of ``deepspeed/linear/``."""

from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig
from deepspeed_tpu.linear.quantization import QuantizedParameter
from deepspeed_tpu.linear.optimized_linear import (OptimizedLinear,
                                                   init_lora_params,
                                                   lora_linear)

__all__ = ["LoRAConfig", "QuantizationConfig", "QuantizedParameter",
           "OptimizedLinear", "init_lora_params", "lora_linear"]
