"""LoRA / quantization configs (ref deepspeed/linear/config.py)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class LoRAConfig:
    """Ref LoRAConfig: rank/alpha plus base-weight sharding — on TPU the
    frozen base weight shards over the "tensor" mesh axis instead of the
    reference's manual 1/world slicing."""
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1
    offload: bool = False
    offload_ratio: float = 0.0
    delay_lora_init: bool = False


@dataclass
class QuantizationConfig:
    """Ref QuantizationConfig: FP-quantized frozen base weights."""
    q_bits: int = 8
    mantissa_bits: int = 3
    group_size: int = 512
