"""PipelineModule API — LayerSpec-based stage partitioning.

Analog of ``deepspeed/runtime/pipe/module.py`` (``LayerSpec`` :30,
``TiedLayerSpec`` :77, ``PipelineModule`` :86 with ``_partition_layers``
:393) and the balanced-partition helpers (``runtime/utils.py``
``partition_uniform`` :606 / ``partition_balanced`` :627).

The functional layer zoo executes homogeneous stacks through the compiled
SPMD pipeline (parallel/pipeline.py); this module provides the
*heterogeneous* LayerSpec surface reference users have: declare arbitrary
layers, choose a partition method (uniform / parameters / type:regex),
inspect the stage boundaries, and run the composed forward.  Tied specs
share one param entry across occurrences (ref TiedLayerSpec embedding
tying).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Stage boundaries with equal layer counts (ref partition_uniform,
    runtime/utils.py:606) → len num_parts+1 prefix list."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    rem = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < rem else 0)
    return parts


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Boundaries minimising the heaviest stage (ref partition_balanced,
    runtime/utils.py:627 — binary search over the bottleneck weight)."""
    n = len(weights)
    if num_parts >= n:
        return partition_uniform(n, num_parts)
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    def parts_needed(limit: float) -> Optional[List[int]]:
        bounds = [0]
        start = 0
        for _ in range(num_parts):
            # furthest end with sum(weights[start:end]) <= limit
            end = int(np.searchsorted(prefix, prefix[start] + limit, "right")) - 1
            if end <= start:
                return None  # one item alone exceeds limit
            bounds.append(min(end, n))
            start = bounds[-1]
            if start >= n:
                break
        if bounds[-1] < n:
            return None
        while len(bounds) < num_parts + 1:
            bounds.append(n)
        return bounds

    lo = float(max(weights))
    hi = float(prefix[-1])
    best = parts_needed(hi)
    for _ in range(60):
        mid = (lo + hi) / 2
        cand = parts_needed(mid)
        if cand is not None:
            best, hi = cand, mid
        else:
            lo = mid
    return best


class LayerSpec:
    """Deferred layer (ref LayerSpec): built lazily on the owning stage.

    ``init_fn(key, *args, **kwargs) -> params``;
    ``apply_fn(params, x) -> x``.  A plain callable (no params) may be
    passed as ``apply_fn`` with ``init_fn=None``.
    """

    def __init__(self, apply_fn: Callable, init_fn: Optional[Callable] = None,
                 *args, **kwargs):
        self.apply_fn = apply_fn
        self.init_fn = init_fn
        self.args = args
        self.kwargs = kwargs

    def build(self, key):
        if self.init_fn is None:
            return None
        return self.init_fn(key, *self.args, **self.kwargs)

    def param_count(self, key) -> int:
        p = self.build(key)
        return 0 if p is None else sum(np.size(x) for x in jax.tree.leaves(p))

    @property
    def typename(self) -> str:
        return getattr(self.apply_fn, "__name__", type(self.apply_fn).__name__)


class TiedLayerSpec(LayerSpec):
    """Share params across occurrences by ``key`` (ref TiedLayerSpec)."""

    def __init__(self, tied_key: str, apply_fn: Callable,
                 init_fn: Optional[Callable] = None, *args, **kwargs):
        super().__init__(apply_fn, init_fn, *args, **kwargs)
        self.tied_key = tied_key


class PipelineModule:
    """LayerSpec list + partitioning (ref PipelineModule :86).

    ``partition_method``: "uniform" | "parameters" | "type:<regex>" (stage
    boundaries balance the count of layers whose typename matches).
    ``num_stages`` defaults to the topology's pipe size (1 without one).
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: int = 1,
                 partition_method: str = "parameters", seed: int = 0):
        self.specs = list(layers)
        self.num_stages = num_stages
        self.partition_method = partition_method
        self._key = jax.random.PRNGKey(seed)
        self.parts = self._partition_layers(partition_method)
        self.params = self._build_params()

    # ------------------------------------------------------------------
    def _partition_layers(self, method: str) -> List[int]:
        n = len(self.specs)
        m = method.lower()
        if m == "uniform":
            return partition_uniform(n, self.num_stages)
        if m == "parameters":
            keys = jax.random.split(self._key, n)
            weights = [max(1, s.param_count(k))
                       for s, k in zip(self.specs, keys)]
            return partition_balanced(weights, self.num_stages)
        if m.startswith("type:"):
            pat = re.compile(method[len("type:"):], re.IGNORECASE)
            weights = [1 if pat.search(s.typename) else 0 for s in self.specs]
            if sum(weights) == 0:
                raise ValueError(f"no layer matches {method!r}")
            return partition_balanced([w + 1e-6 for w in weights],
                                      self.num_stages)
        raise ValueError(f"unknown partition_method {method!r}")

    def stage_of(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    def stage_layers(self, stage: int) -> List[int]:
        return list(range(self.parts[stage], self.parts[stage + 1]))

    # ------------------------------------------------------------------
    def _build_params(self) -> Dict[str, Any]:
        keys = jax.random.split(self._key, len(self.specs))
        params: Dict[str, Any] = {}
        self.tied_comms: Dict[str, List[int]] = {}
        for i, (spec, k) in enumerate(zip(self.specs, keys)):
            if isinstance(spec, TiedLayerSpec):
                self.tied_comms.setdefault(spec.tied_key, []).append(i)
                if spec.tied_key not in params:
                    params[spec.tied_key] = spec.build(k)
            else:
                built = spec.build(k)
                if built is not None:
                    params[f"layer_{i}"] = built
        return params

    def _layer_params(self, params, i: int):
        spec = self.specs[i]
        if isinstance(spec, TiedLayerSpec):
            return params[spec.tied_key]
        return params.get(f"layer_{i}")

    def __call__(self, params, x):
        for i, spec in enumerate(self.specs):
            p = self._layer_params(params, i)
            x = spec.apply_fn(p, x) if p is not None else spec.apply_fn(x)
        return x

    def forward_stage(self, params, x, stage: int):
        """Apply only one stage's layers — the per-stage body handed to
        spmd_pipeline for homogeneous stacks, or to a manual schedule."""
        for i in self.stage_layers(stage):
            p = self._layer_params(params, i)
            fn = self.specs[i].apply_fn
            x = fn(p, x) if p is not None else fn(x)
        return x
