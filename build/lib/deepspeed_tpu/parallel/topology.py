"""Logical device mesh topology.

TPU-native replacement for the reference's process-group machinery
(``deepspeed/utils/groups.py`` + ``runtime/pipe/topology.py``): instead of
materialising torch ProcessGroups per parallelism dimension, we build ONE
``jax.sharding.Mesh`` whose named axes play the role of the reference's
DP/TP/PP/EP/SP groups.  Collectives are expressed against axis names and XLA
lowers them onto ICI/DCN.

Axis order is outer→inner ``(pipe, data, expert, seq, tensor)`` so that the
innermost axes (tensor, seq) — which carry the highest-bandwidth collectives
— map onto adjacent devices/ICI, while pipe/data may ride DCN across hosts.
This mirrors ``PipeModelDataParallelTopology`` (ref topology.py) where model
parallel is innermost.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import logger

# Canonical axis names, outer→inner.
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
# Inner factor of the DP world for hierarchical partitioning: ZeRO++ hpZ
# secondary partition / MiCS sub-groups (ref zero_hpz_partition_size,
# runtime/zero/config.py:300; MiCS_Init, runtime/zero/mics.py:63).  Size 1
# unless the engine factors the DP world; "data" is then the *outer*
# (replication / DCN) factor and "subdata" the *inner* (shard / ICI) one.
SUBDATA_AXIS = "subdata"
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
TENSOR_AXIS = "tensor"
MESH_AXES: Tuple[str, ...] = (PIPE_AXIS, DATA_AXIS, SUBDATA_AXIS, EXPERT_AXIS,
                              SEQ_AXIS, TENSOR_AXIS)

# Axes over which the *global batch* is sharded (ref: DP world = data×expert;
# groups._create_expert_and_data_parallel, groups.py:240).
BATCH_AXES: Tuple[str, ...] = (DATA_AXIS, SUBDATA_AXIS, EXPERT_AXIS)
# Axes over which ZeRO partitions optimizer/gradient/parameter state.
ZERO_AXES: Tuple[str, ...] = (DATA_AXIS, SUBDATA_AXIS, EXPERT_AXIS, SEQ_AXIS)
# Inner (ICI-adjacent) ZeRO axes: the secondary partition group for hpZ
# params / the MiCS shard group.
ZERO_INNER_AXES: Tuple[str, ...] = (SUBDATA_AXIS, EXPERT_AXIS, SEQ_AXIS)


def resolve_mesh_sizes(sizes: Optional[Dict[str, int]], n_devices: int) -> Dict[str, int]:
    """Resolve axis sizes: missing axes default to 1 ("data" defaults to -1),
    one axis may be -1 (inferred). Product < n_devices → submesh (warn).
    Single source of truth shared by MeshTopology and the config system."""
    sizes = dict(sizes or {})
    if DATA_AXIS not in sizes:
        sizes[DATA_AXIS] = -1  # absorb remaining devices by default
    for ax in MESH_AXES:
        sizes.setdefault(ax, 1)
    for ax, v in sizes.items():
        if v != -1 and v <= 0:
            raise ValueError(f"mesh axis {ax} must be positive or -1, got {v}")
    unknown = [ax for ax in MESH_AXES if sizes[ax] == -1]
    prod = int(np.prod([sizes[ax] for ax in MESH_AXES if sizes[ax] != -1]))
    if len(unknown) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if unknown:
        if n_devices % prod != 0:
            raise ValueError(f"{n_devices} devices not divisible by {prod}")
        sizes[unknown[0]] = n_devices // prod
    elif prod > n_devices:
        raise ValueError(f"mesh sizes {sizes} product {prod} > {n_devices} devices")
    elif prod < n_devices:
        logger.warning(f"mesh product {prod} < {n_devices} devices; using a submesh")
    return {ax: int(sizes[ax]) for ax in MESH_AXES}


def factor_data_axis(sizes: Dict[str, int], shard_size: int) -> Dict[str, int]:
    """Factor the resolved data axis into (outer=data, inner=subdata) for
    hierarchical partitioning (hpZ secondary partition / MiCS sub-groups).

    ``shard_size`` devices form the inner shard group (ICI-adjacent); the
    remaining data-parallel factor replicates across them.
    """
    sizes = dict(sizes)
    data = sizes.get(DATA_AXIS, 1) * sizes.get(SUBDATA_AXIS, 1)
    if shard_size <= 0 or data % shard_size != 0:
        raise ValueError(f"data-parallel world {data} not divisible by "
                         f"secondary partition size {shard_size}")
    sizes[DATA_AXIS] = data // shard_size
    sizes[SUBDATA_AXIS] = shard_size
    return sizes


class MeshTopology:
    """A resolved logical mesh over the available devices.

    ``sizes`` maps axis name → size; missing axes default to 1; one axis may
    be -1 (inferred).  The mesh is the single source of truth for every
    "process group" query the reference exposes (``get_data_parallel_world_size``
    etc., ref groups.py:110-663).
    """

    def __init__(self, sizes: Optional[Dict[str, int]] = None,
                 devices: Optional[Sequence] = None):
        devices = list(devices if devices is not None else jax.devices())
        sizes = resolve_mesh_sizes(sizes, len(devices))
        prod = int(np.prod(list(sizes.values())))
        devices = devices[:prod]
        n = prod

        self.sizes: Dict[str, int] = {ax: int(sizes[ax]) for ax in MESH_AXES}
        shape = tuple(self.sizes[ax] for ax in MESH_AXES)
        if n > 1:
            try:
                from jax.experimental import mesh_utils

                dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
            except Exception:
                dev_array = np.asarray(devices).reshape(shape)
        else:
            dev_array = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(dev_array, MESH_AXES)
        logger.info(f"MeshTopology: {self.sizes} over {n} device(s)")

    # -- world-size getters (ref groups.py getters) --------------------
    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.sizes.values())))

    def axis_size(self, axis: str) -> int:
        return self.sizes[axis]

    @property
    def dp_size(self) -> int:
        """Data-parallel world as the reference defines it (data×expert)."""
        return (self.sizes[DATA_AXIS] * self.sizes[SUBDATA_AXIS]
                * self.sizes[EXPERT_AXIS])

    @property
    def zero_size(self) -> int:
        """World over which ZeRO shards state (data×expert×seq): sequence
        parallel ranks hold identical params so they join the ZeRO shard
        group, matching Ulysses+ZeRO-3 composition (ref ulysses_sp.py)."""
        return self.dp_size * self.sizes[SEQ_AXIS]

    @property
    def tp_size(self) -> int:
        return self.sizes[TENSOR_AXIS]

    @property
    def pp_size(self) -> int:
        return self.sizes[PIPE_AXIS]

    @property
    def ep_size(self) -> int:
        return self.sizes[EXPERT_AXIS]

    @property
    def sp_size(self) -> int:
        return self.sizes[SEQ_AXIS]

    # -- sharding helpers ----------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, seq_dim: Optional[int] = None,
                       batch_dim: int = 0, ndim: int = 2) -> NamedSharding:
        """Sharding for a batch array: batch dim over (data, expert), and the
        sequence dim over seq when sequence parallelism is active."""
        spec: List = [None] * ndim
        spec[batch_dim] = BATCH_AXES
        if seq_dim is not None and self.sp_size > 1:
            spec[seq_dim] = SEQ_AXIS
        return NamedSharding(self.mesh, P(*spec))

    def __repr__(self) -> str:  # pragma: no cover
        return f"MeshTopology({self.sizes})"


_GLOBAL_TOPOLOGY: Optional[MeshTopology] = None


def set_topology(topo: MeshTopology) -> None:
    global _GLOBAL_TOPOLOGY
    _GLOBAL_TOPOLOGY = topo


def get_topology() -> Optional[MeshTopology]:
    return _GLOBAL_TOPOLOGY
