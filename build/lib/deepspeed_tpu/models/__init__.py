from deepspeed_tpu.models.registry import get_model_config, list_models, register
from deepspeed_tpu.models.transformer import (TransformerConfig, count_params, forward,
                                              init_params, loss_fn)
