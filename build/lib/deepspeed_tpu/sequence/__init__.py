"""Sequence parallelism & long-context: Ulysses (layer), FPDT (fpdt),
ALST tiled compute (alst)."""

from deepspeed_tpu.sequence.layer import (DistributedAttention,
                                          UlyssesAttentionHF,
                                          single_all_to_all,
                                          ulysses_output_constraint,
                                          ulysses_qkv_constraint)
from deepspeed_tpu.sequence.fpdt import (FPDTAttention, chunked_attention,
                                         chunked_ffn)
from deepspeed_tpu.sequence.alst import (SPDataLoader, sp_shard_batch,
                                         tiled_logits_loss, tiled_mlp)

__all__ = [
    "DistributedAttention", "UlyssesAttentionHF", "single_all_to_all",
    "ulysses_qkv_constraint", "ulysses_output_constraint",
    "FPDTAttention", "chunked_attention", "chunked_ffn",
    "SPDataLoader", "sp_shard_batch", "tiled_logits_loss", "tiled_mlp",
]
