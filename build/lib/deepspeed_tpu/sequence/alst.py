"""ALST — Arctic Long Sequence Training building blocks.

Re-design of the reference's Ulysses-SP HF integration
(``deepspeed/runtime/sequence_parallel/ulysses_sp.py``: ``UlyssesSPAttentionHF``
:49, DataLoader shard adapter :471, ``TiledMLP`` :838, tiled logits+loss
:960).  The attention half lives in :mod:`deepspeed_tpu.sequence.layer`
(Ulysses all-to-all); this module provides the memory-capping tiled compute
and the sequence-sharding data adapter.

TPU-native notes: tiling is a ``lax.scan`` over sequence tiles with
``jax.checkpoint`` per tile, so the backward pass rematerialises one tile at
a time — the same activation-memory bound the reference gets from its
autograd-function tiling, but visible to XLA as a single compiled loop.
The tiled loss never materialises the [B, S, V] logits tensor: each tile
computes logits → log-sum-exp → label pick and only the scalar partial sums
cross tile boundaries.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def tiled_mlp(fn: Callable, x, num_tiles: int, remat: bool = True):
    """Apply ``fn`` over sequence tiles sequentially (ref TiledMLP,
    ulysses_sp.py:838).

    ``fn(x_tile) -> y_tile`` must be pointwise in the sequence dim (true for
    transformer MLPs / layernorms).  x: [B, S, ...] with S divisible by
    ``num_tiles``.  Live activation memory is one tile.
    """
    b, s = x.shape[0], x.shape[1]
    if s % num_tiles != 0:
        raise ValueError(f"seq {s} not divisible by num_tiles {num_tiles}")
    tile = s // num_tiles
    xt = x.reshape((b, num_tiles, tile) + x.shape[2:])
    xt = jnp.moveaxis(xt, 1, 0)  # [N, B, tile, ...]
    body = jax.checkpoint(fn) if remat else fn

    def step(_, xi):
        return None, body(xi)

    _, yt = lax.scan(step, None, xt)
    yt = jnp.moveaxis(yt, 0, 1)
    return yt.reshape((b, s) + yt.shape[3:])


def tiled_logits_loss(hidden, w_embed, labels, num_tiles: int,
                      ignore_index: int = -100,
                      logit_cap: Optional[float] = None):
    """Sequence-tiled cross-entropy without materialising [B, S, V] logits
    (ref tiled logits+loss, ulysses_sp.py:960).

    hidden: [B, S, E]; w_embed: [V, E] (tied output embedding); labels:
    [B, S] int32 with ``ignore_index`` masking.  Returns (mean_loss,
    valid_token_count).
    """
    b, s, e = hidden.shape
    if s % num_tiles != 0:
        raise ValueError(f"seq {s} not divisible by num_tiles {num_tiles}")
    tile = s // num_tiles
    ht = jnp.moveaxis(hidden.reshape(b, num_tiles, tile, e), 1, 0)
    lt = jnp.moveaxis(labels.reshape(b, num_tiles, tile), 1, 0)

    def tile_loss(h_i, y_i):
        # matmul in the input dtype (bf16 on TPU → MXU) with fp32
        # accumulation; fp32 inputs are unchanged
        logits = jnp.einsum("bte,ve->btv", h_i, w_embed,
                            preferred_element_type=jnp.float32)
        if logit_cap is not None:
            logits = logit_cap * jnp.tanh(logits / logit_cap)
        lse = jax.nn.logsumexp(logits, axis=-1)
        y_safe = jnp.where(y_i == ignore_index, 0, y_i)
        gold = jnp.take_along_axis(logits, y_safe[..., None], axis=-1)[..., 0]
        valid = (y_i != ignore_index)
        nll = jnp.where(valid, lse - gold, 0.0)
        return nll.sum(), valid.sum()

    def step(carry, xs):
        loss_sum, count = carry
        h_i, y_i = xs
        li, ci = jax.checkpoint(tile_loss)(h_i, y_i)
        return (loss_sum + li, count + ci), None

    (loss_sum, count), _ = lax.scan(
        step, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)), (ht, lt))
    return loss_sum / jnp.maximum(count, 1).astype(jnp.float32), count


def sp_shard_batch(batch: Dict[str, np.ndarray], sp_rank: int, sp_size: int,
                   seq_keys=("input_ids", "labels", "attention_mask",
                             "position_ids")) -> Dict[str, np.ndarray]:
    """Slice a host batch's sequence dim for one SP rank (ref DataLoader
    shard adapter, ulysses_sp.py:471).

    Each SP rank sees the same samples but a disjoint 1/sp_size slice of the
    sequence; keys not in ``seq_keys`` pass through unsliced.
    """
    if sp_size == 1:
        return dict(batch)
    out = {}
    for key, val in batch.items():
        if key in seq_keys and val is not None and np.ndim(val) >= 2:
            s = val.shape[1]
            if s % sp_size != 0:
                raise ValueError(
                    f"batch['{key}'] seq len {s} not divisible by sp_size {sp_size}")
            shard = s // sp_size
            out[key] = val[:, sp_rank * shard:(sp_rank + 1) * shard]
        else:
            out[key] = val
    return out


class SPDataLoader:
    """Wrap an iterable of host batches, yielding this rank's sequence shard
    (ref UlyssesSPDataLoaderAdapter, ulysses_sp.py:471)."""

    def __init__(self, loader, sp_rank: int, sp_size: int, seq_keys=None):
        self.loader = loader
        self.sp_rank = sp_rank
        self.sp_size = sp_size
        self.seq_keys = tuple(seq_keys) if seq_keys else (
            "input_ids", "labels", "attention_mask", "position_ids")

    def __iter__(self):
        for batch in self.loader:
            yield sp_shard_batch(batch, self.sp_rank, self.sp_size, self.seq_keys)

    def __len__(self):
        return len(self.loader)
