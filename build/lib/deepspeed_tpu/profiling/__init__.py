"""Profiling: XLA-cost-analysis flops profiler (ref deepspeed/profiling/)."""

from deepspeed_tpu.profiling.flops_profiler import (FlopsProfiler,
                                                    get_model_profile, mfu,
                                                    profile_compiled)

__all__ = ["FlopsProfiler", "get_model_profile", "mfu", "profile_compiled"]
