"""FastFileWriter — double-buffered bulk tensor serialization.

Analog of ``deepspeed/io/fast_file_writer.py`` (``FastFileWriter`` :44,
mock/py writers for tests): checkpoint bytes are staged into one of two
pinned host buffers while the other buffer is in flight to storage, so
serialization overlaps I/O.  The flight path is the native AIO handle
(csrc/aio, libaio) when available, plain buffered ``write`` otherwise.

File format (used by FastCheckpointEngine): an 8-byte little-endian header
length, a JSON index {path: {dtype, shape, offset, nbytes}}, then the raw
tensor bytes back to back.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle, aio_available


class _Buffer:
    def __init__(self, nbytes: int):
        self.data = np.empty(nbytes, dtype=np.uint8)
        self.fill = 0

    def room(self) -> int:
        return self.data.size - self.fill

    def put(self, src: np.ndarray) -> int:
        n = min(self.room(), src.size)
        self.data[self.fill:self.fill + n] = src[:n]
        self.fill += n
        return n


class FastFileWriter:
    """Double-buffered writer. ``write(bytes_like)`` → staged; buffers
    flush when full; ``close()`` drains."""

    def __init__(self, path: str, buffer_bytes: int = 32 << 20,
                 use_aio: Optional[bool] = None):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "wb")
        self._bufs = [_Buffer(buffer_bytes), _Buffer(buffer_bytes)]
        self._cur = 0
        self._flusher: Optional[threading.Thread] = None
        self.use_aio = aio_available() if use_aio is None else use_aio
        self._aio = AsyncIOHandle() if self.use_aio else None
        self._offset = 0
        self.bytes_written = 0
        self.flush_count = 0

    # ------------------------------------------------------------------
    def write(self, data) -> int:
        src = np.frombuffer(memoryview(data), dtype=np.uint8)
        written = 0
        while written < src.size:
            buf = self._bufs[self._cur]
            written += buf.put(src[written:])
            if buf.room() == 0:
                self._swap_and_flush()
        return written

    def write_array(self, arr: np.ndarray) -> int:
        return self.write(np.ascontiguousarray(arr).view(np.uint8).reshape(-1))

    # ------------------------------------------------------------------
    def _swap_and_flush(self) -> None:
        self._drain()  # previous in-flight buffer must land first
        buf = self._bufs[self._cur]
        self._cur ^= 1
        self._flusher = threading.Thread(target=self._flush_buf, args=(buf,),
                                         daemon=True)
        self._flusher.start()

    def _flush_buf(self, buf: _Buffer) -> None:
        chunk = buf.data[:buf.fill]
        if self._aio is not None:
            self._aio.pwrite(chunk, self.path, offset=self._offset)
        else:
            self._fh.seek(self._offset)
            self._fh.write(chunk.tobytes())
        self._offset += buf.fill
        self.bytes_written += buf.fill
        self.flush_count += 1
        buf.fill = 0

    def _drain(self) -> None:
        if self._flusher is not None:
            self._flusher.join()
            self._flusher = None

    def close(self) -> Dict[str, Any]:
        self._drain()
        buf = self._bufs[self._cur]
        if buf.fill:
            self._flush_buf(buf)
        self._fh.flush()
        self._fh.close()
        return {"bytes_written": self.bytes_written,
                "flush_count": self.flush_count}


class PyFileWriter:
    """Plain buffered writer with the same interface (ref py writer)."""

    def __init__(self, path: str, **_):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "wb")
        self.bytes_written = 0
        self.flush_count = 0

    def write(self, data) -> int:
        b = bytes(data)
        self._fh.write(b)
        self.bytes_written += len(b)
        return len(b)

    def write_array(self, arr: np.ndarray) -> int:
        return self.write(np.ascontiguousarray(arr).tobytes())

    def close(self) -> Dict[str, Any]:
        self._fh.close()
        return {"bytes_written": self.bytes_written, "flush_count": 0}


class MockFileWriter:
    """Counts bytes, writes nothing (ref deepspeed/io/mock_file_writer.py)."""

    def __init__(self, path: str, **_):
        self.path = path
        self.bytes_written = 0
        self.flush_count = 0

    def write(self, data) -> int:
        self.bytes_written += len(bytes(data))
        return self.bytes_written

    def write_array(self, arr: np.ndarray) -> int:
        self.bytes_written += arr.nbytes
        return arr.nbytes

    def close(self) -> Dict[str, Any]:
        return {"bytes_written": self.bytes_written, "flush_count": 0}


# ----------------------------------------------------------------------
# Indexed tensor-file format
# ----------------------------------------------------------------------

def write_tensor_file(path: str, tensors: Dict[str, np.ndarray],
                      writer_cls=FastFileWriter, **writer_kw) -> Dict[str, Any]:
    """Serialize {path: array} with a JSON index header."""
    index: Dict[str, Any] = {}
    offset = 0
    arrays = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        index[name] = {"dtype": arr.dtype.str, "shape": list(arr.shape),
                       "offset": offset, "nbytes": arr.nbytes}
        offset += arr.nbytes
        arrays.append(arr)
    header = json.dumps(index).encode()
    w = writer_cls(path, **writer_kw)
    w.write(struct.pack("<Q", len(header)))
    w.write(header)
    for arr in arrays:
        w.write_array(arr)
    return w.close()


def read_tensor_index(path: str) -> "Tuple[Dict[str, Any], int]":
    """→ (JSON index, data base offset) without reading any tensor bytes."""
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        return json.loads(f.read(hlen).decode()), 8 + hlen


def read_tensor_entry(path: str, base_offset: int, meta: Dict[str, Any]) -> np.ndarray:
    """Read ONE entry given its index record (targeted seek, no parsing)."""
    with open(path, "rb") as f:
        f.seek(base_offset + meta["offset"])
        raw = f.read(meta["nbytes"])
    return np.frombuffer(raw, dtype=np.dtype(meta["dtype"])
                         ).reshape(meta["shape"]).copy()


def read_tensor_file(path: str, names=None) -> Dict[str, np.ndarray]:
    """Read a tensor file; with ``names`` given, read only those entries
    (the index header + targeted seeks, not the whole file)."""
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        index = json.loads(f.read(hlen).decode())
        base = 8 + hlen
        out = {}
        for name, meta in index.items():
            if names is not None and name not in names:
                continue
            f.seek(base + meta["offset"])
            raw = f.read(meta["nbytes"])
            out[name] = np.frombuffer(raw, dtype=np.dtype(meta["dtype"])
                                      ).reshape(meta["shape"]).copy()
    return out
