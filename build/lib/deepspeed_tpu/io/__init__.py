"""Fast checkpoint I/O (ref deepspeed/io/)."""

from deepspeed_tpu.io.fast_file_writer import (FastFileWriter, MockFileWriter,
                                               PyFileWriter, read_tensor_file,
                                               write_tensor_file)

__all__ = ["FastFileWriter", "PyFileWriter", "MockFileWriter",
           "write_tensor_file", "read_tensor_file"]
