// Shared-memory host collectives for co-located processes.
//
// TPU-native analog of the reference's SHM collectives
// (csrc/cpu/comm/shm.cpp, shm_interface.cpp): when several launcher
// processes share one host, small host-side reductions (grad-norm
// agreement, elastic heartbeats, compressed-collective server phases)
// should ride shared memory, not the network. POSIX shm + a process-shared
// barrier; each rank publishes into its slot, then every rank reduces all
// slots locally (the reference's naive all-reduce path; its tiled
// distributed reduce is an optimization for large payloads that host
// coordination traffic doesn't need).
//
// Plain C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
    // Per-run nonce doubles as the init flag: a crashed previous run leaves
    // its old nonce behind, so late joiners of the NEW run keep waiting
    // until rank 0 has re-initialized the barrier and published the new
    // nonce — no rank can race into a stale pthread_barrier (UB).
    std::atomic<uint64_t> nonce;
    pthread_barrier_t barrier;
};

struct Handle {
    Header* header;
    char* slots;       // world * slot_bytes payload area
    int rank;
    int world;
    int64_t slot_bytes;
    char name[128];
    size_t total_bytes;
};

inline char* slot(Handle* h, int r) { return h->slots + r * h->slot_bytes; }

}  // namespace

extern "C" {

namespace {

void* map_region(const char* name, size_t total, bool create_fresh) {
    int fd;
    if (create_fresh) {
        // retire the stale NAME first: any open that happens after this
        // point reaches the new region, not a crashed run's leftover
        shm_unlink(name);
        fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
        if (fd < 0) fd = shm_open(name, O_CREAT | O_RDWR, 0600);
    } else {
        fd = shm_open(name, O_RDWR, 0600);  // never create: wait for rank 0
    }
    if (fd < 0) return nullptr;
    if (create_fresh && ftruncate(fd, (off_t)total) != 0) {
        close(fd);
        return nullptr;
    }
    struct stat st;
    if (!create_fresh &&
        (fstat(fd, &st) != 0 || (size_t)st.st_size < total)) {
        close(fd);  // region exists but rank 0 hasn't sized it yet
        return nullptr;
    }
    void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    return mem == MAP_FAILED ? nullptr : mem;
}

}  // namespace

// timeout_us bounds how long a non-root rank waits for rank 0 to publish
// this run's nonce (<=0 → 60 s default); on expiry it returns nullptr so
// the caller can raise instead of hanging forever (e.g. when ranks derive
// divergent fallback nonces).
void* ds_shm_create(const char* name, int rank, int world,
                    int64_t slot_bytes, uint64_t nonce, int64_t timeout_us) {
    size_t total = sizeof(Header) + (size_t)world * slot_bytes;
    if (timeout_us <= 0) timeout_us = 60 * 1000 * 1000;

    void* mem = nullptr;
    if (rank == 0) {
        mem = map_region(name, total, /*create_fresh=*/true);
        if (!mem) return nullptr;
    } else {
        // A non-root rank may race ahead of rank 0 and map the previous
        // run's region before rank 0 unlinks it. It waits for this run's
        // nonce with a per-mapping deadline; on expiry it remaps by name —
        // the stale name is gone once rank 0 has run, so the retry
        // converges on the fresh region. (Residual window: a supervisor
        // respawning an identical job without DSTPU_SHM_NONCE can collide
        // nonces; see comm/shm.py.)
        const int64_t remap_us = 2 * 1000 * 1000;
        int64_t total_waited = 0;
        for (;;) {
            while (!(mem = map_region(name, total, false))) {
                usleep(1000);
                total_waited += 1000;
                if (total_waited >= timeout_us) return nullptr;
            }
            Header* hd = (Header*)mem;
            int64_t waited = 0;
            while (hd->nonce.load(std::memory_order_acquire) != nonce &&
                   waited < remap_us && total_waited < timeout_us) {
                usleep(100);
                waited += 100;
                total_waited += 100;
            }
            if (hd->nonce.load(std::memory_order_acquire) == nonce) break;
            munmap(mem, total);  // likely the stale region: remap by name
            mem = nullptr;
            if (total_waited >= timeout_us) return nullptr;
        }
    }

    Handle* h = new Handle();
    h->header = (Header*)mem;
    h->slots = (char*)mem + sizeof(Header);
    h->rank = rank;
    h->world = world;
    h->slot_bytes = slot_bytes;
    h->total_bytes = total;
    snprintf(h->name, sizeof(h->name), "%s", name);

    if (rank == 0) {
        h->header->nonce.store(0, std::memory_order_release);
        pthread_barrierattr_t attr;
        pthread_barrierattr_init(&attr);
        pthread_barrierattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
        pthread_barrier_init(&h->header->barrier, &attr, world);
        pthread_barrierattr_destroy(&attr);
        h->header->nonce.store(nonce, std::memory_order_release);
    }
    return h;
}

static void barrier(Handle* h) { pthread_barrier_wait(&h->header->barrier); }

void ds_shm_barrier(void* hv) { barrier((Handle*)hv); }

// Sum-allreduce of n floats, in place.  Every rank sums the slots in the
// SAME order (0..world-1), so the FP rounding is identical on all ranks
// and the results agree bitwise — required by the grad-norm-agreement and
// elastic-consensus callers.
int ds_shm_allreduce(void* hv, float* data, int64_t n) {
    Handle* h = (Handle*)hv;
    if ((int64_t)(n * sizeof(float)) > h->slot_bytes) return -1;
    memcpy(slot(h, h->rank), data, n * sizeof(float));
    barrier(h);
    const float* first = (const float*)slot(h, 0);
    for (int64_t i = 0; i < n; ++i) data[i] = first[i];
    for (int r = 1; r < h->world; ++r) {
        const float* other = (const float*)slot(h, r);
        for (int64_t i = 0; i < n; ++i) data[i] += other[i];
    }
    barrier(h);  // no one overwrites slots until all have read
    return 0;
}

int ds_shm_broadcast(void* hv, float* data, int64_t n, int root) {
    Handle* h = (Handle*)hv;
    if ((int64_t)(n * sizeof(float)) > h->slot_bytes) return -1;
    if (h->rank == root) memcpy(slot(h, root), data, n * sizeof(float));
    barrier(h);
    if (h->rank != root) memcpy(data, slot(h, root), n * sizeof(float));
    barrier(h);
    return 0;
}

// out must hold world * n floats, laid out rank-major.
int ds_shm_allgather(void* hv, const float* in, int64_t n, float* out) {
    Handle* h = (Handle*)hv;
    if ((int64_t)(n * sizeof(float)) > h->slot_bytes) return -1;
    memcpy(slot(h, h->rank), in, n * sizeof(float));
    barrier(h);
    for (int r = 0; r < h->world; ++r) {
        memcpy(out + r * n, slot(h, r), n * sizeof(float));
    }
    barrier(h);
    return 0;
}

void ds_shm_destroy(void* hv, int unlink_region) {
    Handle* h = (Handle*)hv;
    if (unlink_region) shm_unlink(h->name);
    munmap((void*)h->header, h->total_bytes);
    delete h;
}

}  // extern "C"
