// Asynchronous file I/O engine for NVMe tiering (DeepNVMe equivalent).
//
// TPU-native re-implementation of the reference's AIO stack
// (csrc/aio/common + csrc/aio/py_lib: deepspeed_aio_thread.cpp,
// deepspeed_py_io_handle.cpp): a pthread worker pool drains a task queue of
// pread/pwrite jobs, each optionally split into block_size chunks so
// multiple threads cooperate on one large tensor (the reference's
// single_submit/overlap_events scheduling collapses to queue order here).
// Exposed as a plain C API consumed from Python via ctypes — no pybind11
// in this image.
//
// Build: g++ -O3 -shared -fPIC -pthread ds_aio.cpp -o libds_aio.so

#ifndef _GNU_SOURCE
#define _GNU_SOURCE  // O_DIRECT
#endif

#include <atomic>
#include <condition_variable>
#include <cstdlib>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Task {
    bool write;
    char* buf;
    long nbytes;
    std::string path;
    long file_offset;
    long buf_offset;
    int job_id;
};

struct Handle {
    long block_size;
    int queue_depth;  // max in-flight tasks before submit blocks
    bool use_direct;  // O_DIRECT data path (bypasses the page cache)
    std::vector<std::thread> workers;
    std::deque<Task> queue;
    std::mutex mu;
    std::condition_variable cv_task;   // workers wait for tasks
    std::condition_variable cv_done;   // waiters wait for drain
    std::atomic<long> inflight{0};
    std::atomic<int> next_job{0};
    std::atomic<long> errors{0};
    std::atomic<long> direct_fallbacks{0};  // O_DIRECT chunks served buffered
    bool shutdown = false;

    explicit Handle(long bs, int qd, int n_threads, bool direct)
        : block_size(bs), queue_depth(qd), use_direct(direct) {
        for (int i = 0; i < n_threads; ++i)
            workers.emplace_back([this] { this->worker_loop(); });
    }

    ~Handle() {
        {
            std::lock_guard<std::mutex> lk(mu);
            shutdown = true;
        }
        cv_task.notify_all();
        for (auto& t : workers) t.join();
    }

    void worker_loop() {
        for (;;) {
            Task task;
            {
                std::unique_lock<std::mutex> lk(mu);
                cv_task.wait(lk, [this] { return shutdown || !queue.empty(); });
                if (shutdown && queue.empty()) return;
                task = queue.front();
                queue.pop_front();
            }
            run(task);
            long left = --inflight;
            if (left == 0) cv_done.notify_all();
        }
    }

    // O_DIRECT data path: the aligned body goes through an aligned bounce
    // buffer (user buffers are arbitrary numpy allocations), the unaligned
    // tail through a buffered fd.  Returns false when the file/FS rejects
    // O_DIRECT (e.g. tmpfs) so the caller falls back to buffered I/O.
    bool run_direct(const Task& t) {
        const long A = 4096;
        int flags = t.write ? (O_WRONLY | O_CREAT | O_DIRECT)
                            : (O_RDONLY | O_DIRECT);
        int fd = ::open(t.path.c_str(), flags, 0644);
        if (fd < 0) return false;
        long body = t.nbytes & ~(A - 1);
        char* user = t.buf + t.buf_offset;
        // large numpy buffers are typically page-aligned: skip the bounce
        // copy and do O_DIRECT straight on the user buffer when possible
        bool aligned = ((uintptr_t)user % A) == 0;
        void* bounce = nullptr;
        if (body > 0 && !aligned && posix_memalign(&bounce, A, body) != 0) {
            ::close(fd);
            return false;
        }
        char* io_buf = aligned ? user : (char*)bounce;
        bool ok = true;
        long done = 0;
        if (t.write && body > 0) {
            if (!aligned) memcpy(io_buf, user, body);
            while (done < body) {
                ssize_t r = ::pwrite(fd, io_buf + done, body - done,
                                     t.file_offset + done);
                if (r <= 0) { ok = false; break; }
                done += r;
            }
        } else if (body > 0) {
            while (done < body) {
                ssize_t r = ::pread(fd, io_buf + done, body - done,
                                    t.file_offset + done);
                if (r <= 0) { ok = false; break; }
                done += r;
            }
            if (ok && !aligned) memcpy(user, io_buf, body);
        }
        free(bounce);
        ::close(fd);
        if (!ok && done == 0 && body > 0) return false;  // full fallback
        if (!ok) { ++errors; return true; }
        long tail = t.nbytes - body;
        if (tail > 0) {
            int tf = ::open(t.path.c_str(),
                            t.write ? (O_WRONLY | O_CREAT) : O_RDONLY, 0644);
            if (tf < 0) { ++errors; return true; }
            long td = 0;
            while (td < tail) {
                ssize_t r = t.write
                    ? ::pwrite(tf, user + body + td, tail - td,
                               t.file_offset + body + td)
                    : ::pread(tf, user + body + td, tail - td,
                              t.file_offset + body + td);
                if (r <= 0) { ++errors; break; }
                td += r;
            }
            ::close(tf);
        }
        return true;
    }

    void run(const Task& t) {
        if (use_direct) {
            if ((t.file_offset % 4096) == 0 && run_direct(t)) return;
            ++direct_fallbacks;  // FS rejected O_DIRECT: buffered fallback
        }
        int flags = t.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
        int fd = ::open(t.path.c_str(), flags, 0644);
        if (fd < 0) {
            ++errors;
            return;
        }
        long done = 0;
        while (done < t.nbytes) {
            long chunk = t.nbytes - done;
            ssize_t r = t.write
                ? ::pwrite(fd, t.buf + t.buf_offset + done, chunk, t.file_offset + done)
                : ::pread(fd, t.buf + t.buf_offset + done, chunk, t.file_offset + done);
            if (r <= 0) {
                ++errors;
                break;
            }
            done += r;
        }
        ::close(fd);
    }

    int submit(bool write, char* buf, long nbytes, const char* path, long file_offset) {
        int job = next_job++;
        // split into block_size chunks so the pool parallelises one tensor
        long nchunks = (nbytes + block_size - 1) / block_size;
        {
            std::unique_lock<std::mutex> lk(mu);
            cv_done.wait(lk, [this] {
                return inflight.load() < (long)queue_depth * (long)workers.size() + 1024;
            });
            for (long c = 0; c < nchunks; ++c) {
                long off = c * block_size;
                long len = std::min(block_size, nbytes - off);
                inflight++;
                queue.push_back(Task{write, buf, len, path, file_offset + off, off, job});
            }
        }
        cv_task.notify_all();
        return job;
    }

    long wait_all() {
        std::unique_lock<std::mutex> lk(mu);
        cv_done.wait(lk, [this] { return inflight.load() == 0; });
        return errors.exchange(0);
    }
};

}  // namespace

extern "C" {

void* ds_aio_create(long block_size, int queue_depth, int n_threads,
                    int use_direct) {
    if (block_size <= 0) block_size = 1 << 20;
    if (n_threads <= 0) n_threads = 1;
    return new Handle(block_size, queue_depth, n_threads, use_direct != 0);
}

void ds_aio_destroy(void* h) { delete static_cast<Handle*>(h); }

int ds_aio_pread(void* h, void* buf, long nbytes, const char* path, long offset) {
    return static_cast<Handle*>(h)->submit(false, static_cast<char*>(buf), nbytes, path, offset);
}

int ds_aio_pwrite(void* h, const void* buf, long nbytes, const char* path, long offset) {
    return static_cast<Handle*>(h)->submit(true, const_cast<char*>(static_cast<const char*>(buf)),
                                           nbytes, path, offset);
}

// Blocks until every submitted op completes; returns the number of failed
// chunk ops since the last wait (0 == success).
long ds_aio_wait(void* h) { return static_cast<Handle*>(h)->wait_all(); }

long ds_aio_pending(void* h) { return static_cast<Handle*>(h)->inflight.load(); }

// Chunks that requested O_DIRECT but ran buffered (e.g. tmpfs) since the
// last call — lets callers detect that "direct" numbers measured the cache.
long ds_aio_direct_fallbacks(void* h) {
    return static_cast<Handle*>(h)->direct_fallbacks.exchange(0);
}

}  // extern "C"
