// Native host optimizers for ZeRO-Offload: fused Adam/AdamW, Adagrad, Lion.
//
// TPU-native analog of the reference's SIMD CPU optimizers
// (csrc/adam/cpu_adam_impl.cpp, csrc/adagrad/cpu_adagrad.cpp,
// csrc/lion/cpu_lion_impl.cpp, csrc/includes/simd.h): the reference
// hand-writes AVX512/AVX256 intrinsics; here each loop is written to
// auto-vectorize (-O3 -march=native, OpenMP parallel for + simd), which on
// x86-64 emits the same AVX fused steps without freezing the ISA at build
// time. Exposed as a plain C ABI for ctypes (no pybind11 in this image).
//
// All state is fp32 host memory owned by Python (numpy); updates are
// in-place. `step` is the 1-based Adam step for bias correction.

#include <cmath>
#include <cstdint>

#if defined(_OPENMP)
#include <omp.h>
#define PARALLEL_FOR _Pragma("omp parallel for simd")
#else
#define PARALLEL_FOR
#endif

extern "C" {

// Fused Adam / AdamW (adamw != 0 -> decoupled weight decay).
void ds_adam_step(float* param, const float* grad, float* exp_avg,
                  float* exp_avg_sq, int64_t n, float lr, float beta1,
                  float beta2, float eps, float weight_decay, int step,
                  int adamw) {
    const float bc1 = 1.0f - std::pow(beta1, (float)step);
    const float bc2 = 1.0f - std::pow(beta2, (float)step);
    const float step_size = lr / bc1;
    const float inv_sqrt_bc2 = 1.0f / std::sqrt(bc2);
    const float decoupled = (adamw && weight_decay != 0.0f)
                                ? lr * weight_decay : 0.0f;
    PARALLEL_FOR
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i];
        float p = param[i];
        if (!adamw && weight_decay != 0.0f) g += weight_decay * p;
        float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
        float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        float denom = std::sqrt(v) * inv_sqrt_bc2 + eps;
        param[i] = p - decoupled * p - step_size * m / denom;
    }
}

// Adagrad (ref cpu_adagrad.cpp).
void ds_adagrad_step(float* param, const float* grad, float* exp_avg_sq,
                     int64_t n, float lr, float eps, float weight_decay) {
    PARALLEL_FOR
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i];
        if (weight_decay != 0.0f) g += weight_decay * param[i];
        float v = exp_avg_sq[i] + g * g;
        exp_avg_sq[i] = v;
        param[i] -= lr * g / (std::sqrt(v) + eps);
    }
}

// Lion (ref cpu_lion_impl.cpp): sign-of-interpolated-momentum update.
void ds_lion_step(float* param, const float* grad, float* exp_avg, int64_t n,
                  float lr, float beta1, float beta2, float weight_decay) {
    PARALLEL_FOR
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i];
        float m = exp_avg[i];
        float c = beta1 * m + (1.0f - beta1) * g;
        float update = (c > 0.0f) - (c < 0.0f);  // sign(c)
        if (weight_decay != 0.0f) update += weight_decay * param[i];
        param[i] -= lr * update;
        exp_avg[i] = beta2 * m + (1.0f - beta2) * g;
    }
}

}  // extern "C"
