"""NVMe/AIO performance tuning (ref deepspeed/nvme/)."""

from deepspeed_tpu.nvme.perf_sweep import run_sweep, sweep_main

__all__ = ["run_sweep", "sweep_main"]
