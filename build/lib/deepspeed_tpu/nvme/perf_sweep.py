"""AIO parameter sweep — find the best (block_size, queue_depth) for this
host's storage.

Analog of ``deepspeed/nvme/`` (``perf_run_sweep.py``, the ``ds_nvme_tune``
CLI): writes/reads a scratch file across a grid of AIO settings, reports
GB/s, and emits the best config as the ``aio`` JSON block users paste into
their config.  Uses the native AIO handle (csrc/aio) when built, falling
back to buffered I/O so the tool still ranks block sizes on any host.
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle, aio_available
from deepspeed_tpu.utils.logging import logger

DEFAULT_BLOCK_SIZES = [256 << 10, 1 << 20, 4 << 20, 8 << 20]
DEFAULT_QUEUE_DEPTHS = [4, 8, 16, 32]


def _bench_one(path: str, data: np.ndarray, block_size: int, queue_depth: int,
               read: bool, use_direct: bool = False):
    """→ (GB/s, direct_effective) for one configuration."""
    direct_effective = use_direct
    if aio_available():
        h = AsyncIOHandle(block_size=block_size, queue_depth=queue_depth,
                          use_direct=use_direct)
        t0 = time.perf_counter()
        if read:
            h.pread(data, path)
        else:
            h.pwrite(data, path)
        dt = time.perf_counter() - t0
        if use_direct and h.direct_fallbacks() > 0:
            direct_effective = False  # FS rejected O_DIRECT: cache numbers
    else:  # buffered fallback: block_size still matters, queue_depth doesn't
        t0 = time.perf_counter()
        if read:
            with open(path, "rb", buffering=0) as f:
                for off in range(0, data.nbytes, block_size):
                    f.read(block_size)
        else:
            with open(path, "wb", buffering=0) as f:
                view = data.view(np.uint8).reshape(-1)
                for off in range(0, data.nbytes, block_size):
                    f.write(view[off:off + block_size].tobytes())
                f.flush()
                os.fsync(f.fileno())
        dt = time.perf_counter() - t0
    return data.nbytes / dt / 1e9, direct_effective


def run_sweep(nvme_dir: str, io_bytes: int = 64 << 20,
              block_sizes: Optional[List[int]] = None,
              queue_depths: Optional[List[int]] = None) -> Dict[str, Any]:
    """Sweep read+write and return results + best aio config."""
    block_sizes = block_sizes or DEFAULT_BLOCK_SIZES
    queue_depths = queue_depths or DEFAULT_QUEUE_DEPTHS
    os.makedirs(nvme_dir, exist_ok=True)
    path = os.path.join(nvme_dir, "_dstpu_sweep.bin")
    data = np.random.default_rng(0).integers(
        0, 255, size=io_bytes, dtype=np.uint8)
    results = []
    try:
        for bs in block_sizes:
            for qd in (queue_depths if aio_available() else [queue_depths[0]]):
                # buffered vs O_DIRECT: direct measures the device, not the
                # page cache (ref csrc/aio O_DIRECT discipline)
                for direct in ([False, True] if aio_available() else [False]):
                    wr, d_ok = _bench_one(path, data, bs, qd, read=False,
                                          use_direct=direct)
                    rd, d_ok2 = _bench_one(path, data, bs, qd, read=True,
                                           use_direct=direct)
                    eff = direct and d_ok and d_ok2
                    results.append({"block_size": bs, "queue_depth": qd,
                                    "use_direct": direct,
                                    "direct_effective": eff,
                                    "write_gbps": wr, "read_gbps": rd,
                                    "score": min(wr, rd)})
                    logger.info(f"aio sweep bs={bs} qd={qd} direct={direct}"
                                f"{'' if eff == direct else ' (FELL BACK)'}: "
                                f"write {wr:.2f} GB/s read {rd:.2f} GB/s")
    finally:
        if os.path.exists(path):
            os.remove(path)
    # recommend from DIRECT rows when the FS honors O_DIRECT: buffered
    # scores are page-cache-inflated and mispredict real NVMe behaviour;
    # buffered rows remain in `results` for the cache-speed comparison
    direct_rows = [r for r in results if r.get("direct_effective")]
    pool = direct_rows or results
    best = max(pool, key=lambda r: r["score"])
    return {
        "results": results,
        "best": best,
        "direct_honored": bool(direct_rows),
        "aio_config": {"block_size": best["block_size"],
                       "queue_depth": best["queue_depth"],
                       "use_direct": bool(best.get("use_direct", False)),
                       "single_submit": False, "overlap_events": True,
                       "thread_count": 1},
        "native_aio": aio_available(),
    }


def sweep_main(argv=None) -> int:
    """`dstpu_nvme_tune` entry point (ref bin/ds_nvme_tune)."""
    ap = argparse.ArgumentParser(description="AIO/NVMe performance sweep")
    ap.add_argument("--nvme_dir", required=True)
    ap.add_argument("--io_size", type=int, default=64 << 20)
    ap.add_argument("--json", default=None, help="write results to this file")
    args = ap.parse_args(argv)
    out = run_sweep(args.nvme_dir, io_bytes=args.io_size)
    print(json.dumps(out["aio_config"], indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    return 0


if __name__ == "__main__":
    raise SystemExit(sweep_main())
