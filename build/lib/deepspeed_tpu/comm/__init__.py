"""deepspeed_tpu.comm — collectives façade (ref: deepspeed/comm)."""

from deepspeed_tpu.comm.comm import (ReduceOp, all_gather, all_reduce, all_to_all, allgather,
                                     allreduce, axis_index, barrier, broadcast,
                                     get_local_rank, get_rank, get_world_size,
                                     init_distributed, is_initialized, ppermute,
                                     reduce_scatter)
