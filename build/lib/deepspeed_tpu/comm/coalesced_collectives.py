"""Coalesced & quantized collectives (ZeRO-3 / ZeRO++ comm paths).

TPU-native analog of ``runtime/comm/coalesced_collectives.py``:

* ``reduce_scatter_coalesced`` (ref :158) — one fused reduce-scatter over a
  whole gradient pytree: leaves are flattened and concatenated into a single
  padded buffer so the mesh sees ONE collective, then shards are split back.
* ``all_to_all_quant_reduce`` (ref :31, the qgZ schedule of ZeRO++) — int8
  block-quantized two-level gradient reduction: quantize → all-to-all within
  the inner (intra-node / ICI) axis → dequant-reduce → quantize → all-to-all
  across the outer (inter-node / DCN) axis → dequant-reduce.  Wire traffic is
  int8 both hops, matching qgZ's 4× reduction vs fp32.
* ``loco_quant_reduce`` (ref :81) — qgZ with error feedback (LoCo): the
  quantization residual is carried to the next step instead of dropped.

All functions are **in-jit** collectives: call them inside ``shard_map``
(the engine does) with the relevant mesh axis names.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.ops.quantizer import dequantize_blockwise, quantize_blockwise

AxisName = Union[str, Sequence[str]]


def _axis_size(axis: AxisName) -> jnp.ndarray:
    return lax.psum(1, axis)


def _flatten_concat(tree, world: int) -> Tuple[jnp.ndarray, Any, list]:
    """Concatenate all leaves into one f32 vector padded to ``world``."""
    leaves, treedef = jax.tree.flatten(tree)
    flat = [jnp.ravel(x).astype(jnp.float32) for x in leaves]
    sizes = [int(x.size) for x in flat]
    total = sum(sizes)
    pad = (-total) % world
    buf = jnp.concatenate(flat + ([jnp.zeros((pad,), jnp.float32)] if pad else []))
    return buf, treedef, sizes


def _split_restore(buf: jnp.ndarray, treedef, sizes, shapes, dtypes):
    out, off = [], 0
    for size, shape, dt in zip(sizes, shapes, dtypes):
        out.append(buf[off:off + size].reshape(shape).astype(dt))
        off += size
    return jax.tree.unflatten(treedef, out)


def reduce_scatter_coalesced(tree, axis: AxisName, world: int):
    """Fused reduce-scatter of a pytree (ref coalesced_collectives.py:158).

    Returns ``(shard, meta)``: this rank's 1/world shard of the flat reduced
    buffer plus the metadata to reassemble (used by ZeRO-2 partitioned
    gradient consumers).  ``world`` must be the static axis size.
    """
    buf, treedef, sizes = _flatten_concat(tree, world)
    shard = lax.psum_scatter(buf, axis, scatter_dimension=0, tiled=True)
    return shard, (treedef, sizes)


def all_gather_coalesced(shard: jnp.ndarray, meta, shapes, dtypes, axis: AxisName):
    """Inverse: gather shards and restore the pytree (ref ZeRO-3
    AllGatherCoalescedHandle, partition_parameters.py:704)."""
    treedef, sizes = meta
    buf = lax.all_gather(shard, axis, axis=0, tiled=True)
    return _split_restore(buf, treedef, sizes, shapes, dtypes)


# ----------------------------------------------------------------------
# qgZ: quantized two-level all-to-all gradient reduce (ZeRO++)
# ----------------------------------------------------------------------
def _quant_chunked_reduce(x: jnp.ndarray, axis: AxisName, world: int,
                          num_bits: int, group_size: int) -> jnp.ndarray:
    """One level of qgZ: chunk → quantize → all-to-all → dequant → mean.

    ``x`` is the local [N] buffer (N divisible by world); returns this
    rank's [N/world] reduced chunk. int8 + f32-scales travel the wire.
    """
    m = x.size // world
    chunks = x.reshape(world, m)
    gs = min(group_size, m)
    if m % gs:
        gs = m
    q, scale, _ = quantize_blockwise(chunks, num_bits=num_bits, group_size=gs)
    # every rank receives chunk r from all ranks: [world, m] rows=src rank
    q_t = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    s_t = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0, tiled=True)
    deq = dequantize_blockwise(q_t.reshape(world, m), s_t.reshape(world, -1))
    return jnp.mean(deq, axis=0)


def all_to_all_quant_reduce(tree, inner_axis: AxisName, outer_axis: AxisName,
                            inner_size: int, outer_size: int,
                            num_bits: int = 8, group_size: int = 256):
    """qgZ (ref coalesced_collectives.py:31): hierarchical int8 gradient
    reduction.  Level 1 rides the inner axis (ICI), level 2 the outer axis
    (DCN).  Returns ``(shard, meta)`` like :func:`reduce_scatter_coalesced`
    — this rank's 1/(inner·outer) shard of the mean gradient.
    """
    world = inner_size * outer_size
    buf, treedef, sizes = _flatten_concat(tree, world)
    lvl1 = _quant_chunked_reduce(buf, inner_axis, inner_size, num_bits, group_size)
    if outer_size > 1:
        lvl2 = _quant_chunked_reduce(lvl1, outer_axis, outer_size, num_bits, group_size)
    else:
        lvl2 = lvl1
    return lvl2, (treedef, sizes)


def loco_quant_reduce(tree, err_tree, inner_axis: AxisName, outer_axis: AxisName,
                      inner_size: int, outer_size: int,
                      num_bits: int = 8, group_size: int = 256):
    """LoCo variant (ref coalesced_collectives.py:81): error feedback carries
    the quantization residual of the *sent* values into the next step.

    ``err_tree`` must match ``tree``; returns (shard, meta, new_err_tree).
    """
    world = inner_size * outer_size
    comp = jax.tree.map(lambda g, e: g + e, tree, err_tree)
    buf, treedef, sizes = _flatten_concat(comp, world)
    # residual of the first (lossy) send is what error feedback tracks
    m = buf.size // inner_size
    gs = min(group_size, m)
    if m % gs:
        gs = m
    q, scale, _ = quantize_blockwise(buf.reshape(inner_size, m), num_bits=num_bits,
                                     group_size=gs)
    sent = dequantize_blockwise(q, scale).reshape(-1)
    residual_flat = buf - sent
    shapes = [jnp.shape(x) for x in jax.tree.leaves(tree)]
    dtypes = [jnp.result_type(x) for x in jax.tree.leaves(err_tree)]
    new_err = _split_restore(residual_flat, treedef, sizes, shapes, dtypes)

    lvl1 = _quant_chunked_reduce(buf, inner_axis, inner_size, num_bits, group_size)
    lvl2 = (_quant_chunked_reduce(lvl1, outer_axis, outer_size, num_bits, group_size)
            if outer_size > 1 else lvl1)
    return lvl2, (treedef, sizes), new_err


def tree_meta(tree):
    """Shapes/dtypes needed to reassemble after gather."""
    leaves = jax.tree.leaves(tree)
    return [jnp.shape(x) for x in leaves], [jnp.result_type(x) for x in leaves]
