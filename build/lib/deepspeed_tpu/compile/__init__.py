"""DeepCompile-analog: compiler-analysis-driven memory/schedule passes
(ref deepspeed/compile/)."""

from deepspeed_tpu.compile.backend import (CompilePass, CompileReport,
                                           OffloadOptStatesPass, ProfilePass,
                                           RematPass, deepspeed_compile)

__all__ = ["deepspeed_compile", "CompilePass", "CompileReport",
           "ProfilePass", "RematPass", "OffloadOptStatesPass"]
