// Shared-memory host collectives for co-located processes.
//
// TPU-native analog of the reference's SHM collectives
// (csrc/cpu/comm/shm.cpp, shm_interface.cpp): when several launcher
// processes share one host, small host-side reductions (grad-norm
// agreement, elastic heartbeats, compressed-collective server phases)
// should ride shared memory, not the network. POSIX shm + a process-shared
// barrier; each rank publishes into its slot, then every rank reduces all
// slots locally (the reference's naive all-reduce path; its tiled
// distributed reduce is an optimization for large payloads that host
// coordination traffic doesn't need).
//
// Plain C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
    std::atomic<int> init_done;
    pthread_barrier_t barrier;
};

struct Handle {
    Header* header;
    char* slots;       // world * slot_bytes payload area
    int rank;
    int world;
    int64_t slot_bytes;
    char name[128];
    size_t total_bytes;
};

inline char* slot(Handle* h, int r) { return h->slots + r * h->slot_bytes; }

}  // namespace

extern "C" {

void* ds_shm_create(const char* name, int rank, int world,
                    int64_t slot_bytes) {
    size_t total = sizeof(Header) + (size_t)world * slot_bytes;
    int fd = shm_open(name, O_CREAT | O_RDWR, 0600);
    if (fd < 0) return nullptr;
    if (ftruncate(fd, (off_t)total) != 0) { close(fd); return nullptr; }
    void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    if (mem == MAP_FAILED) return nullptr;

    Handle* h = new Handle();
    h->header = (Header*)mem;
    h->slots = (char*)mem + sizeof(Header);
    h->rank = rank;
    h->world = world;
    h->slot_bytes = slot_bytes;
    h->total_bytes = total;
    snprintf(h->name, sizeof(h->name), "%s", name);

    if (rank == 0) {
        pthread_barrierattr_t attr;
        pthread_barrierattr_init(&attr);
        pthread_barrierattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
        pthread_barrier_init(&h->header->barrier, &attr, world);
        pthread_barrierattr_destroy(&attr);
        h->header->init_done.store(1, std::memory_order_release);
    } else {
        while (h->header->init_done.load(std::memory_order_acquire) != 1) {
            usleep(100);
        }
    }
    return h;
}

static void barrier(Handle* h) { pthread_barrier_wait(&h->header->barrier); }

void ds_shm_barrier(void* hv) { barrier((Handle*)hv); }

// Sum-allreduce of n floats, in place.
int ds_shm_allreduce(void* hv, float* data, int64_t n) {
    Handle* h = (Handle*)hv;
    if ((int64_t)(n * sizeof(float)) > h->slot_bytes) return -1;
    memcpy(slot(h, h->rank), data, n * sizeof(float));
    barrier(h);
    // every rank reduces all slots into its private buffer
    for (int r = 0; r < h->world; ++r) {
        if (r == h->rank) continue;
        const float* other = (const float*)slot(h, r);
        for (int64_t i = 0; i < n; ++i) data[i] += other[i];
    }
    barrier(h);  // no one overwrites slots until all have read
    return 0;
}

int ds_shm_broadcast(void* hv, float* data, int64_t n, int root) {
    Handle* h = (Handle*)hv;
    if ((int64_t)(n * sizeof(float)) > h->slot_bytes) return -1;
    if (h->rank == root) memcpy(slot(h, root), data, n * sizeof(float));
    barrier(h);
    if (h->rank != root) memcpy(data, slot(h, root), n * sizeof(float));
    barrier(h);
    return 0;
}

// out must hold world * n floats, laid out rank-major.
int ds_shm_allgather(void* hv, const float* in, int64_t n, float* out) {
    Handle* h = (Handle*)hv;
    if ((int64_t)(n * sizeof(float)) > h->slot_bytes) return -1;
    memcpy(slot(h, h->rank), in, n * sizeof(float));
    barrier(h);
    for (int r = 0; r < h->world; ++r) {
        memcpy(out + r * n, slot(h, r), n * sizeof(float));
    }
    barrier(h);
    return 0;
}

void ds_shm_destroy(void* hv, int unlink_region) {
    Handle* h = (Handle*)hv;
    if (unlink_region) shm_unlink(h->name);
    munmap((void*)h->header, h->total_bytes);
    delete h;
}

}  // extern "C"
